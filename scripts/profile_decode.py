"""Decode hot-loop phase profile on the current accelerator.

Builds a const-init engine (same construction as bench.py's rungs), drives
a fixed batch of greedy requests, and prints one JSON line with per-phase
wall time from the engine's DYN_ENGINE_PHASE_TIMING accounting
(decode.schedule / upload / dispatch / readback / retire / post) plus ITL
and throughput.  Exists to answer "where do the decode milliseconds go" —
which, behind a tunneled PJRT transport with ~6ms/sync RTT, is dominated
by host<->device round-trips rather than compute (the thing the fused
decode_steps>1 path, the overlapped decode pipeline, and upload caching
exist to amortize).

A/B mode (``--ab``) runs the same workload twice — synchronous decode
(``decode_overlap=False``) then the overlapped pipeline — and reports
steps/s plus each mode's per-phase share of decode wall.  Exits nonzero
when overlap regresses throughput below ``--ab-min-speedup`` (default:
any regression fails).  In overlap mode the synchronous ``decode.readback``
phase disappears by construction: the wait moves to ``decode.retire``,
which runs while the NEXT window computes on device.

Mixed A/B mode (``--mixed``) drives a CONTINUOUS ARRIVAL stream — requests
land every ``--arrival-ms`` while earlier ones decode, with chunked prefill
on — twice: the split prefill/decode step, then the ragged unified-batch
step (``unified_batch=True``).  Reports steps/s (scheduler iterations over
wall), the admission-drain count (pipeline drains forced by new-sequence
admission — the sync point the unified step removes; must stay 0 in
unified mode), unified-window count, and per-phase shares.  Exits nonzero
when unified regresses steps/s below ``--mixed-min-speedup``.

``--family`` picks the model family for the mixed A/B: ``llama`` (the
``--model`` LlamaConfig geometry), ``moe`` (Mixtral tiny_moe routed
experts) or ``mla`` (DeepSeek tiny_mla latent attention) — every family
with a registered unified forward.  ``--decode-heavy`` switches the
arrival pattern to one burst plus a mid-decode straggler: the window is
decode lanes wall-to-wall, the regime the packed-lane kernel exists for.

Usage: python scripts/profile_decode.py [--model llama32_1b|tiny]
           [--quant int8] [--isl 256] [--osl 64] [--batch 16]
           [--decode-steps 1] [--overlap 0|1] [--ab]
           [--mixed] [--family llama|moe|mla] [--decode-heavy]
           [--requests 12] [--arrival-ms 50] [--chunk 32]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["DYN_ENGINE_PHASE_TIMING"] = "1"


def _family_setup(args: argparse.Namespace):
    """Resolve ``--family`` to (registry key, model config, model label)."""
    from dynamo_tpu.models.llama import LlamaConfig

    family = getattr(args, "family", "llama") or "llama"
    if family == "llama":
        return "llama", getattr(LlamaConfig, args.model)(), args.model
    if family == "moe":
        from dynamo_tpu.models.mixtral import MixtralConfig

        return "mixtral", MixtralConfig.tiny_moe(), "tiny_moe"
    if family == "mla":
        from dynamo_tpu.models.deepseek import DeepseekConfig

        return "deepseek_v2", DeepseekConfig.tiny_mla(), "tiny_mla"
    raise SystemExit(f"unknown --family {family!r} (llama|moe|mla)")


def _decode_phase_shares(phase_ms: dict) -> dict:
    """Each decode.* phase's share of total decode wall (0..1)."""
    decode = {k: v["total_ms"] for k, v in phase_ms.items() if k.startswith("decode.")}
    total = sum(decode.values())
    if total <= 0:
        return {}
    return {k: round(v / total, 4) for k, v in decode.items()}


async def run(args: argparse.Namespace, *, overlap: bool | None = None) -> dict:
    import jax
    import numpy as np

    from dynamo_tpu.engine.engine import EngineConfig, JaxLlmEngine
    from dynamo_tpu.llm.protocols.common import (
        Annotated,
        LLMEngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.registry import get_family
    from dynamo_tpu.models.llama import LlamaConfig

    cfg = getattr(LlamaConfig, args.model)()
    family = get_family("llama")
    max_len = args.isl + args.osl + 16
    block_size = 16
    num_blocks = args.batch * ((max_len + block_size - 1) // block_size) + 8

    def shaped(k):
        p = family.init_params(cfg, k)
        if args.quant and args.quant != "none":
            from dynamo_tpu.ops.quant import quantize_params

            p = quantize_params(p, family.quant_leaves)
        return p

    shapes = jax.eval_shape(shaped, jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda s: np.full(
            s.shape, 1 if np.issubdtype(s.dtype, np.integer) else 0.01,
            dtype=s.dtype,
        ),
        shapes,
    )
    engine = JaxLlmEngine(
        EngineConfig(
            model=cfg,
            num_blocks=num_blocks,
            block_size=block_size,
            max_batch_size=args.batch,
            max_model_len=max_len,
            prefill_buckets=(args.isl,),
            decode_steps=args.decode_steps,
            top_logprobs_k=0,
            logit_bias_k=0,
            quantize=None if args.quant in (None, "none") else args.quant,
            kv_cache_dtype=args.kv_dtype,
            decode_overlap=overlap,
        ),
        params=params,
    )
    engine.start()
    mode = "overlap" if engine.decode_overlap else "sync"
    print(f"profile: engine up ({args.model}, {mode})", file=sys.stderr)
    rng = np.random.default_rng(0)

    from dynamo_tpu.runtime.engine import Context

    def make_request() -> dict:
        tokens = rng.integers(10, cfg.vocab_size - 10, size=args.isl).tolist()
        return PreprocessedRequest(
            token_ids=tokens,
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=args.osl, ignore_eos=True),
            eos_token_ids=[],
        ).to_wire()

    itls: list[float] = []
    started = 0
    all_started = asyncio.Event()

    async def drive(req: dict) -> int:
        nonlocal started
        t0 = time.monotonic()
        ttft = t_last = None
        count = 0
        stream = await engine.generate(Context(req))
        async for item in stream:
            ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
            if ann.data is None or not ann.data.token_ids:
                continue
            t_last = time.monotonic()
            if ttft is None:
                ttft = t_last - t0
                started += 1
                if started == args.batch:
                    all_started.set()
            count += len(ann.data.token_ids)
        if ttft is not None and count > 1:
            itls.append((t_last - t0 - ttft) / (count - 1))
        return count

    t0 = time.monotonic()
    await drive(make_request())  # warmup: compiles
    print(f"profile: warmup {time.monotonic()-t0:.1f}s", file=sys.stderr)
    itls.clear()
    before = engine.stats()
    steps_before = before.get("decode_steps_total", 0)
    # delta the window counters too: cumulative totals would include
    # warmup and not reconcile with the steady-state phase stats
    over_before = before.get("decode_windows_overlapped_total", 0)
    sync_before = before.get("decode_windows_sync_total", 0)

    # Steady-state isolation: phase stats restart once every lane has
    # produced a first token, so prefill interleave doesn't pollute the
    # decode-window attribution (a window's readback otherwise waits on
    # queued prefill programs and bills them to decode).
    async def clear_at_steady():
        await all_started.wait()
        engine.phase_stats.clear()

    t0 = time.monotonic()
    results = await asyncio.gather(
        clear_at_steady(), *[drive(make_request()) for _ in range(args.batch)]
    )
    counts = results[1:]
    wall = time.monotonic() - t0
    stats = engine.stats()
    engine.stop()
    dev = jax.devices()[0]
    phase_ms = stats.get("phase_ms", {})
    decode_steps = stats.get("decode_steps_total", 0) - steps_before
    return {
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "model": args.model,
        "quant": args.quant,
        "batch": args.batch,
        "isl": args.isl,
        "osl": args.osl,
        "decode_steps": args.decode_steps,
        "overlap": engine.decode_overlap,
        "windows_overlapped": stats.get("decode_windows_overlapped_total", 0) - over_before,
        "windows_sync": stats.get("decode_windows_sync_total", 0) - sync_before,
        "wall_s": round(wall, 2),
        "tok_s": round(sum(counts) / wall, 1),
        "steps_s": round(decode_steps / wall, 2),
        "itl_mean_ms": round(1e3 * sum(itls) / max(len(itls), 1), 2),
        "decode_phase_share": _decode_phase_shares(phase_ms),
        "phase_ms": phase_ms,
    }


async def run_mixed(args: argparse.Namespace, *, unified: bool) -> dict:
    """One continuous-arrival mixed prefill+decode run (chunked prefill on,
    overlap per ``--overlap``/engine default) on the split or the unified
    step.  ``steps_s`` counts DECODE steps (see the inline note below);
    raw scheduler iterations ride along as ``iterations``."""
    import jax
    import numpy as np

    from dynamo_tpu.engine.engine import EngineConfig, JaxLlmEngine
    from dynamo_tpu.llm.protocols.common import (
        Annotated,
        LLMEngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.registry import get_family
    from dynamo_tpu.runtime.engine import Context

    fam_name, cfg, model_label = _family_setup(args)
    family = get_family(fam_name)
    max_len = args.isl + args.osl + 16
    block_size = 16
    num_blocks = args.batch * ((max_len + block_size - 1) // block_size) + 8
    shapes = jax.eval_shape(
        lambda k: family.init_params(cfg, k), jax.random.PRNGKey(0)
    )
    params = jax.tree.map(
        lambda s: np.full(
            s.shape, 1 if np.issubdtype(s.dtype, np.integer) else 0.01,
            dtype=s.dtype,
        ),
        shapes,
    )
    overlap = None if args.overlap is None else bool(args.overlap)
    engine = JaxLlmEngine(
        EngineConfig(
            model=cfg,
            model_family=fam_name,
            num_blocks=num_blocks,
            block_size=block_size,
            max_batch_size=args.batch,
            max_model_len=max_len,
            prefill_buckets=(args.isl,),
            prefill_chunk_tokens=args.chunk,
            top_logprobs_k=0,
            logit_bias_k=0,
            # model-dtype cache in BOTH modes: the unified step auto-disables
            # on narrowed cache dtypes (parity contract), and an A/B must
            # not compare different cache byte counts anyway
            kv_cache_dtype=None,
            decode_overlap=overlap,
            unified_batch=unified,
        ),
        params=params,
    )
    engine.start()
    mode = "unified" if engine.unified_batch else "split"
    print(f"profile: mixed engine up ({model_label}, {mode})", file=sys.stderr)
    rng = np.random.default_rng(0)

    def make_request() -> dict:
        tokens = rng.integers(10, cfg.vocab_size - 10, size=args.isl).tolist()
        return PreprocessedRequest(
            token_ids=tokens,
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=args.osl, ignore_eos=True),
            eos_token_ids=[],
        ).to_wire()

    async def drive(req: dict) -> int:
        count = 0
        stream = await engine.generate(Context(req))
        async for item in stream:
            ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
            if ann.data is not None:
                count += len(ann.data.token_ids)
        return count

    # warmup: two OVERLAPPING requests, so the mixed-window buckets (chunk
    # plus live decode lanes) compile here and not mid-measurement
    warm = [asyncio.ensure_future(drive(make_request()))]
    await asyncio.sleep(args.arrival_ms / 1e3)
    warm.append(asyncio.ensure_future(drive(make_request())))
    await asyncio.gather(*warm)
    before = engine.stats()
    engine.phase_stats.clear()
    t0 = time.monotonic()
    tasks = []
    if getattr(args, "decode_heavy", False):
        # decode-heavy packing scenario: admit everything in one burst so
        # the steady-state window is decode lanes wall-to-wall (the regime
        # the packed-lane kernel compresses from one block per lane to
        # dense rows), then one straggler lands mid-decode to prove a
        # chunk can still ride a packed decode window
        for _ in range(args.requests - 1):
            tasks.append(asyncio.ensure_future(drive(make_request())))
        await asyncio.sleep(args.arrival_ms / 1e3)
        tasks.append(asyncio.ensure_future(drive(make_request())))
    else:
        for _ in range(args.requests):
            tasks.append(asyncio.ensure_future(drive(make_request())))
            await asyncio.sleep(args.arrival_ms / 1e3)
    counts = await asyncio.gather(*tasks)
    wall = time.monotonic() - t0
    stats = engine.stats()
    engine.stop()
    dev = jax.devices()[0]
    # decode-step cadence, not scheduler iterations: a unified iteration
    # serves prefill AND decode in one window, so raw iteration counts
    # would under-credit exactly the merge being measured
    steps = stats["decode_steps_total"] - before["decode_steps_total"]
    return {
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "model": model_label,
        "family": getattr(args, "family", "llama") or "llama",
        "mode": mode,
        "decode_heavy": bool(getattr(args, "decode_heavy", False)),
        "iterations": (
            stats["iterations_total"] - before["iterations_total"]
        ),
        "batch": args.batch,
        "isl": args.isl,
        "osl": args.osl,
        "chunk": args.chunk,
        "requests": args.requests,
        "arrival_ms": args.arrival_ms,
        "overlap": engine.decode_overlap,
        "wall_s": round(wall, 2),
        "tok_s": round(sum(counts) / wall, 1),
        "steps_s": round(steps / wall, 2),
        "admission_drains": (
            stats["admission_drains_total"] - before["admission_drains_total"]
        ),
        "windows_unified": (
            stats["decode_windows_unified_total"]
            - before["decode_windows_unified_total"]
        ),
        "windows_overlapped": (
            stats["decode_windows_overlapped_total"]
            - before["decode_windows_overlapped_total"]
        ),
        "windows_sync": (
            stats["decode_windows_sync_total"]
            - before["decode_windows_sync_total"]
        ),
        "decode_phase_share": _decode_phase_shares(stats.get("phase_ms", {})),
        "phase_ms": stats.get("phase_ms", {}),
    }


async def amain(args: argparse.Namespace) -> tuple[int, dict]:
    """Run the requested profile; returns (exit_code, result).  Importable
    so the tier-1 smoke tests can drive the A/Bs in-process."""
    if getattr(args, "mixed", False):
        split = await run_mixed(args, unified=False)
        uni = await run_mixed(args, unified=True)
        speedup = uni["steps_s"] / split["steps_s"] if split["steps_s"] else 0.0
        result = {
            "mixed": True,
            "model": uni["model"],
            "family": uni["family"],
            "decode_heavy": uni["decode_heavy"],
            "batch": args.batch,
            "isl": args.isl,
            "osl": args.osl,
            "chunk": args.chunk,
            "requests": args.requests,
            "arrival_ms": args.arrival_ms,
            "unified_speedup_steps_s": round(speedup, 3),
            "unified_speedup_tok_s": round(
                uni["tok_s"] / split["tok_s"], 3
            ) if split["tok_s"] else 0.0,
            "admission_drains_split": split["admission_drains"],
            "admission_drains_unified": uni["admission_drains"],
            "windows_unified": uni["windows_unified"],
            "split": split,
            "unified": uni,
        }
        rc = 0
        if speedup < args.mixed_min_speedup:
            print(
                f"profile: unified REGRESSED steps/s ({speedup:.3f}x < "
                f"{args.mixed_min_speedup}x)", file=sys.stderr,
            )
            rc = 1
        if uni["windows_unified"] and uni["admission_drains"]:
            print(
                "profile: unified mode still drained on admission "
                f"({uni['admission_drains']} drains)", file=sys.stderr,
            )
            rc = 1
        return rc, result
    if not args.ab:
        overlap = None if args.overlap is None else bool(args.overlap)
        return 0, await run(args, overlap=overlap)

    sync = await run(args, overlap=False)
    over = await run(args, overlap=True)
    speedup = over["tok_s"] / sync["tok_s"] if sync["tok_s"] else 0.0
    result = {
        "ab": True,
        "model": args.model,
        "batch": args.batch,
        "isl": args.isl,
        "osl": args.osl,
        "decode_steps": args.decode_steps,
        "overlap_speedup_tok_s": round(speedup, 3),
        "overlap_speedup_steps_s": round(
            over["steps_s"] / sync["steps_s"], 3
        ) if sync["steps_s"] else 0.0,
        "readback_share_sync": sync["decode_phase_share"].get("decode.readback", 0.0),
        "readback_share_overlap": over["decode_phase_share"].get("decode.readback", 0.0),
        "retire_share_overlap": over["decode_phase_share"].get("decode.retire", 0.0),
        "sync": sync,
        "overlap": over,
    }
    rc = 0
    if speedup < args.ab_min_speedup:
        print(
            f"profile: overlap REGRESSED throughput ({speedup:.3f}x < "
            f"{args.ab_min_speedup}x)", file=sys.stderr,
        )
        rc = 1
    return rc, result


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="llama32_1b",
                        help="LlamaConfig classmethod name (llama32_1b, tiny, ...)")
    parser.add_argument("--quant", default="none")
    parser.add_argument("--kv-dtype", default="bf16")
    parser.add_argument("--isl", type=int, default=256)
    parser.add_argument("--osl", type=int, default=64)
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--decode-steps", type=int, default=1)
    parser.add_argument("--overlap", type=int, choices=(0, 1), default=None,
                        help="force the overlapped pipeline on/off "
                             "(default: engine default / DYN_DECODE_OVERLAP)")
    parser.add_argument("--ab", action="store_true",
                        help="run sync AND overlap, report both + speedup; "
                             "exit nonzero if overlap regresses throughput")
    parser.add_argument("--ab-min-speedup", type=float, default=1.0,
                        help="minimum overlap/sync tok_s ratio for --ab to "
                             "exit 0 (1.0 = fail on any regression)")
    parser.add_argument("--mixed", action="store_true",
                        help="continuous-arrival mixed prefill+decode A/B: "
                             "split step vs ragged unified-batch step; exit "
                             "nonzero if unified regresses steps/s or still "
                             "drains on admission")
    parser.add_argument("--mixed-min-speedup", type=float, default=1.0,
                        help="minimum unified/split steps_s ratio for "
                             "--mixed to exit 0")
    parser.add_argument("--family", default="llama",
                        choices=("llama", "moe", "mla"),
                        help="--mixed: model family (llama uses --model; "
                             "moe/mla use the tiny Mixtral/DeepSeek "
                             "geometries)")
    parser.add_argument("--decode-heavy", action="store_true",
                        help="--mixed: burst admission + one mid-decode "
                             "straggler — windows are packed decode lanes "
                             "nearly wall-to-wall")
    parser.add_argument("--requests", type=int, default=12,
                        help="--mixed: requests in the arrival stream")
    parser.add_argument("--arrival-ms", type=int, default=50,
                        help="--mixed: inter-arrival gap (tight enough that "
                             "admissions land while earlier requests decode)")
    parser.add_argument("--chunk", type=int, default=32,
                        help="--mixed: prefill_chunk_tokens for both modes")
    parser.add_argument("--out", default=None,
                        help="also write the JSON result to this path")
    args = parser.parse_args()
    rc, result = asyncio.run(amain(args))
    # shared provenance header (dynamo_tpu/bench/perfgate.py): lets the perf
    # gate refuse to diff artifacts from an incompatible schema generation
    from dynamo_tpu.bench.perfgate import provenance_stamp

    result["provenance"] = provenance_stamp()
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result))
    return rc


if __name__ == "__main__":
    sys.exit(main())
