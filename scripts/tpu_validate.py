"""One-shot TPU validation: compile + run every Pallas kernel and the
quantized/fp8 paths on tiny shapes against their XLA twins, printing one
JSON line per check.  Designed to extract maximum signal from a briefly
healthy accelerator (the axon tunnel can wedge for hours): each check is
independent, failures don't stop later checks, and the whole run takes
seconds once compiles land.

Usage:  python scripts/tpu_validate.py            # real device
        JAX_PLATFORMS=cpu python scripts/...      # CPU (interpret off)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check(name):
    def wrap(fn):
        CHECKS.append((name, fn))
        return fn

    return wrap


CHECKS: list = []
INTERPRET = False  # set in main(): True off-TPU (Mosaic needs real hardware)


@check("paged_attention_gqa")
def _gqa():
    import jax, jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.attention import paged_decode_attention
    from dynamo_tpu.ops.pallas import paged_attention_decode

    rng = np.random.default_rng(0)
    k = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.bfloat16)
    tables = jnp.asarray(rng.integers(0, 8, (2, 4)), jnp.int32)
    ctx = jnp.asarray([13, 7], jnp.int32)
    out = np.asarray(paged_attention_decode(q, k, v, tables, ctx, interpret=INTERPRET))
    ref = np.asarray(paged_decode_attention(q, k, v, tables, ctx))
    rel = float(np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-9))
    assert rel < 0.05, rel
    return {"rel": round(rel, 5)}


@check("paged_window_attention")
def _window():
    import jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.attention import paged_window_attention
    from dynamo_tpu.ops.pallas import paged_window_attention_decode

    rng = np.random.default_rng(1)
    k = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((2, 3, 8, 128)), jnp.bfloat16)
    tables = jnp.asarray(rng.integers(0, 8, (2, 4)), jnp.int32)
    ctx = jnp.asarray([15, 9], jnp.int32)
    out = np.asarray(paged_window_attention_decode(q, k, v, tables, ctx, interpret=INTERPRET))
    ref = np.asarray(paged_window_attention(q, k, v, tables, ctx))
    rel = float(np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-9))
    assert rel < 0.05, rel
    return {"rel": round(rel, 5)}


@check("mla_kernels")
def _mla():
    import jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.pallas.mla_attention import (
        mla_paged_attention_decode,
        mla_paged_window_attention_decode,
    )

    rng = np.random.default_rng(2)
    ck = jnp.asarray(rng.standard_normal((8, 8, 128)), jnp.bfloat16)
    kr = jnp.asarray(rng.standard_normal((8, 8, 64)), jnp.bfloat16)
    q_lat = jnp.asarray(rng.standard_normal((2, 4, 128)), jnp.bfloat16)
    q_rope = jnp.asarray(rng.standard_normal((2, 4, 64)), jnp.bfloat16)
    tables = jnp.asarray(rng.integers(0, 8, (2, 3)), jnp.int32)
    ctx = jnp.asarray([10, 6], jnp.int32)
    out = mla_paged_attention_decode(q_lat, q_rope, ck, kr, tables, ctx, scale=0.07, interpret=INTERPRET)
    assert np.isfinite(np.asarray(out)).all()
    q_lat_w = jnp.asarray(rng.standard_normal((2, 2, 4, 128)), jnp.bfloat16)
    q_rope_w = jnp.asarray(rng.standard_normal((2, 2, 4, 64)), jnp.bfloat16)
    out_w = mla_paged_window_attention_decode(
        q_lat_w, q_rope_w, ck, kr, tables, ctx + 1, scale=0.07, interpret=INTERPRET
    )
    assert np.isfinite(np.asarray(out_w)).all()
    return {}


@check("block_copy")
def _copy():
    import jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.pallas import gather_blocks, scatter_blocks

    pool = jnp.arange(8 * 8 * 128, dtype=jnp.bfloat16).reshape(8, 8, 128)
    ids = jnp.asarray([3, 1, 6], jnp.int32)
    g = gather_blocks(pool, ids, interpret=INTERPRET)
    out = scatter_blocks(jnp.zeros_like(pool), g, jnp.asarray([0, 4, 7], jnp.int32), interpret=INTERPRET)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(pool[3]))
    return {}


@check("int8_matmul")
def _int8():
    import jax, jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.quant import mm, quantize_matrix

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((128, 512)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((512, 256)) * 0.05, jnp.float32)
    qm = quantize_matrix(w)
    t0 = time.monotonic()
    out = np.asarray(jax.jit(mm)(x, qm))
    ref = np.asarray(x.astype(jnp.float32) @ w)
    rel = float(np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-9))
    assert rel < 0.05, rel
    return {"rel": round(rel, 4), "s": round(time.monotonic() - t0, 2)}


@check("fp8_cache_ops")
def _fp8():
    import jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.attention import paged_decode_attention, write_decode_kv
    from dynamo_tpu.ops.pallas import paged_attention_decode

    fp8 = jnp.dtype("float8_e4m3fn")
    rng = np.random.default_rng(4)
    k = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.float32).astype(fp8)
    v = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.float32).astype(fp8)
    k2, v2 = write_decode_kv(
        k, v, jnp.ones((1, 2, 128), jnp.float32), jnp.ones((1, 2, 128), jnp.float32),
        jnp.asarray([5], jnp.int32),
    )
    assert k2.dtype == fp8
    q = jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.bfloat16)
    tables = jnp.asarray(rng.integers(0, 8, (2, 4)), jnp.int32)
    ctx = jnp.asarray([13, 7], jnp.int32)
    out = np.asarray(paged_attention_decode(q, k2, v2, tables, ctx, interpret=INTERPRET))
    ref = np.asarray(paged_decode_attention(q, k2, v2, tables, ctx))
    rel = float(np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-9))
    assert rel < 0.08, rel
    return {"rel": round(rel, 4)}


def main() -> int:
    import jax

    dev = jax.devices()[0]
    global INTERPRET
    INTERPRET = dev.platform != "tpu"
    print(json.dumps({"device": str(dev), "platform": dev.platform,
                      "interpret": INTERPRET}))
    failed = 0
    for name, fn in CHECKS:
        t0 = time.monotonic()
        try:
            extra = fn() or {}
            print(json.dumps({"check": name, "ok": True,
                              "s": round(time.monotonic() - t0, 1), **extra}))
        except Exception as exc:  # noqa: BLE001 — independent checks
            failed += 1
            print(json.dumps({"check": name, "ok": False,
                              "error": f"{type(exc).__name__}: {exc}"[:300]}))
        sys.stdout.flush()
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
