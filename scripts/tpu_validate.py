"""One-shot TPU validation: compile + run every Pallas kernel and the
quantized/fp8 paths on tiny shapes against their XLA twins, printing one
JSON line per check.  Designed to extract maximum signal from a briefly
healthy accelerator (the axon tunnel can wedge for hours): each check is
independent, failures don't stop later checks, and the whole run takes
seconds once compiles land.

Usage:  python scripts/tpu_validate.py            # real device
        JAX_PLATFORMS=cpu python scripts/...      # CPU (interpret off)
        python scripts/tpu_validate.py --bench [--out KERNEL_PERF.json]
            # kernel microbenchmarks: Pallas paged attention vs the XLA
            # gather fallback, gather_blocks vs fancy indexing — per-shape
            # us/iter + effective GB/s, written as a kernel-perf table that
            # the engine's attention_impl="auto" consults (engine.py).
            # Off-TPU results are recorded with interpret=true and are
            # NEVER consulted by the engine (Mosaic interpret-mode timings
            # say nothing about hardware).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check(name):
    def wrap(fn):
        CHECKS.append((name, fn))
        return fn

    return wrap


CHECKS: list = []
INTERPRET = False  # set in main(): True off-TPU (Mosaic needs real hardware)


@check("paged_attention_gqa")
def _gqa():
    import jax, jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.attention import paged_decode_attention
    from dynamo_tpu.ops.pallas import paged_attention_decode

    rng = np.random.default_rng(0)
    k = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.bfloat16)
    tables = jnp.asarray(rng.integers(0, 8, (2, 4)), jnp.int32)
    ctx = jnp.asarray([13, 7], jnp.int32)
    out = np.asarray(paged_attention_decode(q, k, v, tables, ctx, interpret=INTERPRET))
    ref = np.asarray(paged_decode_attention(q, k, v, tables, ctx))
    rel = float(np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-9))
    assert rel < 0.05, rel
    return {"rel": round(rel, 5)}


@check("paged_window_attention")
def _window():
    import jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.attention import paged_window_attention
    from dynamo_tpu.ops.pallas import paged_window_attention_decode

    rng = np.random.default_rng(1)
    k = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((2, 3, 8, 128)), jnp.bfloat16)
    tables = jnp.asarray(rng.integers(0, 8, (2, 4)), jnp.int32)
    ctx = jnp.asarray([15, 9], jnp.int32)
    out = np.asarray(paged_window_attention_decode(q, k, v, tables, ctx, interpret=INTERPRET))
    ref = np.asarray(paged_window_attention(q, k, v, tables, ctx))
    rel = float(np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-9))
    assert rel < 0.05, rel
    return {"rel": round(rel, 5)}


@check("mla_kernels")
def _mla():
    import jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.pallas.mla_attention import (
        mla_paged_attention_decode,
        mla_paged_window_attention_decode,
    )

    rng = np.random.default_rng(2)
    ck = jnp.asarray(rng.standard_normal((8, 8, 128)), jnp.bfloat16)
    kr = jnp.asarray(rng.standard_normal((8, 8, 64)), jnp.bfloat16)
    q_lat = jnp.asarray(rng.standard_normal((2, 4, 128)), jnp.bfloat16)
    q_rope = jnp.asarray(rng.standard_normal((2, 4, 64)), jnp.bfloat16)
    tables = jnp.asarray(rng.integers(0, 8, (2, 3)), jnp.int32)
    ctx = jnp.asarray([10, 6], jnp.int32)
    out = mla_paged_attention_decode(q_lat, q_rope, ck, kr, tables, ctx, scale=0.07, interpret=INTERPRET)
    assert np.isfinite(np.asarray(out)).all()
    q_lat_w = jnp.asarray(rng.standard_normal((2, 2, 4, 128)), jnp.bfloat16)
    q_rope_w = jnp.asarray(rng.standard_normal((2, 2, 4, 64)), jnp.bfloat16)
    out_w = mla_paged_window_attention_decode(
        q_lat_w, q_rope_w, ck, kr, tables, ctx + 1, scale=0.07, interpret=INTERPRET
    )
    assert np.isfinite(np.asarray(out_w)).all()
    return {}


@check("block_copy")
def _copy():
    import jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.pallas import gather_blocks, scatter_blocks

    pool = jnp.arange(8 * 8 * 128, dtype=jnp.bfloat16).reshape(8, 8, 128)
    ids = jnp.asarray([3, 1, 6], jnp.int32)
    g = gather_blocks(pool, ids, interpret=INTERPRET)
    out = scatter_blocks(jnp.zeros_like(pool), g, jnp.asarray([0, 4, 7], jnp.int32), interpret=INTERPRET)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(pool[3]))
    return {}


@check("int8_matmul")
def _int8():
    import jax, jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.quant import mm, quantize_matrix

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((128, 512)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((512, 256)) * 0.05, jnp.float32)
    qm = quantize_matrix(w)
    t0 = time.monotonic()
    out = np.asarray(jax.jit(mm)(x, qm))
    ref = np.asarray(x.astype(jnp.float32) @ w)
    rel = float(np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-9))
    assert rel < 0.05, rel
    return {"rel": round(rel, 4), "s": round(time.monotonic() - t0, 2)}


@check("fp8_cache_ops")
def _fp8():
    import jax.numpy as jnp, numpy as np  # noqa: E401

    from dynamo_tpu.ops.attention import paged_decode_attention, write_decode_kv
    from dynamo_tpu.ops.pallas import paged_attention_decode

    fp8 = jnp.dtype("float8_e4m3fn")
    rng = np.random.default_rng(4)
    k = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.float32).astype(fp8)
    v = jnp.asarray(rng.standard_normal((8, 8, 2, 128)), jnp.float32).astype(fp8)
    k2, v2 = write_decode_kv(
        k, v, jnp.ones((1, 2, 128), jnp.float32), jnp.ones((1, 2, 128), jnp.float32),
        jnp.asarray([5], jnp.int32),
    )
    assert k2.dtype == fp8
    q = jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.bfloat16)
    tables = jnp.asarray(rng.integers(0, 8, (2, 4)), jnp.int32)
    ctx = jnp.asarray([13, 7], jnp.int32)
    out = np.asarray(paged_attention_decode(q, k2, v2, tables, ctx, interpret=INTERPRET))
    ref = np.asarray(paged_decode_attention(q, k2, v2, tables, ctx))
    rel = float(np.linalg.norm(out - ref) / max(np.linalg.norm(ref), 1e-9))
    assert rel < 0.08, rel
    return {"rel": round(rel, 4)}


# ---------------------------------------------------------------------------
# kernel microbenchmarks (--bench)
# ---------------------------------------------------------------------------


def _time_us(fn, *args, iters: int, chain=None) -> float:
    """Median-of-3 timing of ``iters`` dispatches (one final sync), after a
    warmup call that eats the compile.

    ``chain(args, out) -> args`` feeds each iteration's output back into the
    next iteration's inputs.  This is mandatory for honest numbers on the
    tunneled axon platform: back-to-back *identical* dispatches measured
    >10 TB/s effective bandwidth on a v5e (HBM peak ~0.82 TB/s), i.e. repeat
    executions of the same (executable, args) pair are elided or overlapped
    somewhere below us.  A data dependency between iterations defeats that.

    The end-of-loop sync is a HOST READBACK of one element of the final
    output, not ``block_until_ready`` — measured on the same platform,
    block_until_ready returns before on-device completion, which let a
    first version of this timer report 8300 TFLOP/s on a 197 TFLOP/s chip.
    The readback transitively waits on the whole dependent chain; the
    calibration rows (bench_calibration) verify the resulting ceiling."""
    import jax
    import numpy as np
    import jax.numpy as jnp

    def sync(out):
        leaf = out[0] if isinstance(out, tuple) else jax.tree.leaves(out)[0]
        return float(jnp.ravel(leaf)[0])  # device slice + scalar fetch

    sync(fn(*args))  # compile + warm
    samples = []
    for _ in range(3):
        a = args
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(*a)
            if chain is not None:
                a = chain(a, out)
        sync(out)
        samples.append((time.perf_counter() - t0) / iters)
    return sorted(samples)[1] * 1e6


def bench_attention(iters: int) -> list[dict]:
    """Pallas paged-attention decode vs the XLA gather fallback — the
    measurement behind engine.py's attention_impl="auto" choice."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.ops.attention import paged_decode_attention
    from dynamo_tpu.ops.pallas import paged_attention_decode

    rows = []
    # (batch, ctx) — decode-regime shapes bracketing the headline geometry
    # (ISL 3000, batch 16, 8B-class heads) plus the high-batch / long-ctx
    # corner where the kernel's page-skipping matters.  Interpret mode
    # (off-TPU) runs a token small set: placeholders, never consulted.
    shapes = (
        ((2, 128),)
        if INTERPRET
        else ((4, 1024), (16, 1024), (16, 3072), (32, 2048), (64, 1024))
    )
    for batch, ctx in shapes:
        kvh, d, bs = 8, 128, 16
        nblocks_seq = (ctx + bs - 1) // bs
        pool = batch * nblocks_seq + 8
        rng = np.random.default_rng(0)
        k = jnp.asarray(rng.standard_normal((pool, bs, kvh, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((pool, bs, kvh, d)), jnp.bfloat16)
        q = jnp.asarray(rng.standard_normal((batch, 32, d)), jnp.bfloat16)
        tables = jnp.asarray(
            rng.permutation(pool)[: batch * nblocks_seq].reshape(batch, nblocks_seq),
            jnp.int32,
        )
        ctx_lens = jnp.full((batch,), ctx, jnp.int32)

        pallas_fn = jax.jit(
            lambda q, k, v, t, c: paged_attention_decode(
                q, k, v, t, c, interpret=INTERPRET
            )
        )
        xla_fn = jax.jit(paged_decode_attention)
        # serialize iterations by feeding the output (same shape/dtype as q,
        # values bounded — a convex combination of v) back in as the query
        chain = lambda a, out: (out,) + a[1:]  # noqa: E731
        us_p = _time_us(pallas_fn, q, k, v, tables, ctx_lens, iters=iters,
                        chain=chain)
        us_x = _time_us(xla_fn, q, k, v, tables, ctx_lens, iters=iters,
                        chain=chain)
        # effective bandwidth: every decode step streams the context's K+V
        bytes_kv = 2 * batch * ctx * kvh * d * 2  # bf16
        rows.append(
            {
                "bench": "paged_attention_decode",
                "batch": batch,
                "ctx": ctx,
                "pallas_us": round(us_p, 1),
                "xla_us": round(us_x, 1),
                "pallas_gbps": round(bytes_kv / us_p / 1e3, 1),
                "xla_gbps": round(bytes_kv / us_x / 1e3, 1),
                "pallas_speedup": round(us_x / us_p, 3),
            }
        )
    return rows


def bench_block_copy(iters: int) -> list[dict]:
    """gather_blocks (Pallas) vs XLA fancy indexing — the extract path of
    KV transfer/offload (engine._jit_extract uses the XLA form today)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.ops.pallas import gather_blocks

    rows = []
    for n_gather in (4,) if INTERPRET else (16, 64, 256):
        pool_n, bs, kvh, d = (64, 16, 8, 128) if INTERPRET else (2048, 16, 8, 128)
        rng = np.random.default_rng(1)
        pool = jnp.asarray(
            rng.standard_normal((pool_n, bs, kvh, d)), jnp.bfloat16
        )
        ids = jnp.asarray(rng.permutation(pool_n)[:n_gather], jnp.int32)

        # each iteration gathers a different (data-dependently derived) id
        # set so repeat dispatches can't be elided — see _time_us
        def _next_ids(i, g):
            bump = 1 + jnp.int32(jnp.abs(g[0, 0, 0, 0].astype(jnp.float32)) < 0)
            return (i + bump) % pool_n

        pallas_fn = jax.jit(
            lambda p, i: (g := gather_blocks(p, i, interpret=INTERPRET),
                          _next_ids(i, g))
        )
        xla_fn = jax.jit(lambda p, i: (g := p[i], _next_ids(i, g)))
        chain = lambda a, out: (a[0], out[1])  # noqa: E731
        us_p = _time_us(pallas_fn, pool, ids, iters=iters, chain=chain)
        us_x = _time_us(xla_fn, pool, ids, iters=iters, chain=chain)
        bytes_moved = n_gather * bs * kvh * d * 2 * 2  # read + write, bf16
        rows.append(
            {
                "bench": "gather_blocks",
                "n_blocks": n_gather,
                "pallas_us": round(us_p, 1),
                "xla_us": round(us_x, 1),
                "pallas_gbps": round(bytes_moved / us_p / 1e3, 1),
                "xla_gbps": round(bytes_moved / us_x / 1e3, 1),
                "pallas_speedup": round(us_x / us_p, 3),
            }
        )
    return rows


def bench_ragged_packed(iters: int) -> list[dict]:
    """Packed decode lanes vs the padded per-lane-block layout, through the
    SAME ragged kernel — the measurement behind the unified step's dense
    packing.  A decode-heavy window of N single-token lanes used to burn N
    mostly-empty token blocks (each lane padded to its own block); per-row
    lane routing packs them into ceil(N/tb) blocks.  blocks_* and
    block_reduction are host-side packing facts (hardware-independent —
    the tier-1 regression diff gates on them); the timings are only
    meaningful compiled on real hardware."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.ops.pallas import pack_page_meta, ragged_paged_attention

    rows = []
    tb = 8
    # decode-heavy windows: every lane one token at the context tail
    shapes = (
        ((8, 32), (16, 32)) if INTERPRET else ((8, 1024), (16, 1024), (16, 3072))
    )
    qh, kvh, d = (4, 2, 128) if INTERPRET else (32, 8, 128)
    bs = 8 if INTERPRET else 16
    for lanes, ctx in shapes:
        nblocks_seq = (ctx + bs - 1) // bs
        pool = lanes * nblocks_seq + 8
        rng = np.random.default_rng(0)
        k = jnp.asarray(rng.standard_normal((pool, bs, kvh, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((pool, bs, kvh, d)), jnp.bfloat16)
        tables = np.asarray(
            rng.permutation(pool)[: lanes * nblocks_seq].reshape(
                lanes, nblocks_seq
            ),
            np.int32,
        )

        def layout(packed: bool):
            # packed: lanes share token blocks densely; padded: each lane
            # rounds up to its own whole block (the pre-packing layout)
            t = -(-lanes // tb) * tb if packed else lanes * tb
            token_lane = np.full((t,), lanes, np.int32)
            token_pos = np.full((t,), -1, np.int32)
            for lane in range(lanes):
                row = lane if packed else lane * tb
                token_lane[row] = lane
                token_pos[row] = ctx - 1
            meta = pack_page_meta(
                token_lane, token_pos, tables, tb_tokens=tb, block_size=bs
            )
            q = jnp.asarray(
                rng.standard_normal((t, qh, d)), jnp.bfloat16
            )
            args = (q, k, v, jnp.asarray(token_lane), jnp.asarray(token_pos),
                    *(jnp.asarray(a) for a in meta))
            return args, t // tb

        fn = jax.jit(
            lambda q, k, v, tl, tp, pp, pl, po, pc: ragged_paged_attention(
                q, k, v, tl, tp, pp, pl, po, pc, tb_tokens=tb,
                interpret=INTERPRET,
            ).astype(q.dtype)
        )
        chain = lambda a, out: (out,) + a[1:]  # noqa: E731
        args_packed, blocks_packed = layout(packed=True)
        args_padded, blocks_padded = layout(packed=False)
        us_packed = _time_us(fn, *args_packed, iters=iters, chain=chain)
        us_padded = _time_us(fn, *args_padded, iters=iters, chain=chain)
        rows.append(
            {
                "bench": "ragged_packed_decode",
                "lanes": lanes,
                "ctx": ctx,
                "tb_tokens": tb,
                "blocks_packed": blocks_packed,
                "blocks_padded": blocks_padded,
                "block_reduction": round(blocks_padded / blocks_packed, 2),
                "packed_us": round(us_packed, 1),
                "padded_us": round(us_padded, 1),
                "packed_speedup": round(us_padded / us_packed, 3),
            }
        )
    return rows


# the standard autotuned geometries: the tiny tier-1 test shape and the
# llama3-8b serving shape.  The cost-model rows for these are COMMITTED in
# KERNEL_PERF.json (tests/bench/test_kernel_perf_ragged.py ratchets them),
# and --out rewrites the whole table, so the bench must regenerate them.
AUTOTUNE_GEOMETRIES = (
    # (num_heads, num_kv_heads, head_dim, block_size, lanes,
    #  max_blocks_per_seq, dtypes, buckets)
    (4, 2, 16, 4, 4, 32, ("float32",), (16, 32, 64, 128)),
    (32, 8, 128, 16, 16, 256, ("float32", "bfloat16", "float8_e4m3fn"),
     (32, 64, 128, 256, 512, 1024, 2048, 4096)),
)


def bench_autotune(iters: int) -> list[dict]:
    """Ragged-kernel tunable sweep (ops/autotune.py): tb_tokens x
    page_slots x pages_per_step per geometry.  Off-TPU the deterministic
    cost model scores the grid (hardware-independent rows, device_kind=
    "any"); on real hardware each candidate is additionally WALL-CLOCK
    timed over a synthetic decode-heavy window and the measured winner is
    stamped with this chip's device_kind.  The swept grid prints to
    stdout per candidate; only winner rows enter the table."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.ops import autotune
    from dynamo_tpu.ops.pallas import pack_page_meta, ragged_paged_attention

    dev = jax.devices()[0]
    rows = []
    for h, kvh, d, bs, lanes, mb, dtypes, buckets in AUTOTUNE_GEOMETRIES:
        geom = autotune.Geometry(
            num_heads=h, num_kv_heads=kvh, head_dim=d, block_size=bs,
            lanes=lanes, max_blocks_per_seq=mb,
        )
        for dtype in dtypes:
            # hardware-independent cost-model winner (always emitted: the
            # committed rows the tier-1 ratchet diffs must survive --out)
            modeled = autotune.sweep(geom, dtype=dtype, buckets=buckets)
            for cand in modeled.pop("grid"):
                print(json.dumps({"bench": "autotune_grid",
                                  "geometry": geom.key, "dtype": dtype,
                                  "source": "cost_model", **cand}))
            rows.append(modeled)
        if INTERPRET:
            continue  # interpret wall clocks say nothing about hardware

        # measured sweep at the serving dtype: time the compiled kernel on
        # this chip over the decode-heavy synthetic window
        jdt = jnp.bfloat16
        rng = np.random.default_rng(0)
        pool = lanes * mb + 8
        k = jnp.asarray(rng.standard_normal((pool, bs, kvh, d)), jdt)
        v = jnp.asarray(rng.standard_normal((pool, bs, kvh, d)), jdt)

        def runner(cand):
            tb = cand["tb_tokens"]
            ps = cand["page_slots"]
            pps = cand["pages_per_step"]
            token_lane, token_pos, bt = autotune._synthetic_workloads(
                geom, tb
            )[0]
            try:
                meta = pack_page_meta(
                    token_lane, token_pos, bt, tb_tokens=tb,
                    block_size=bs, page_slots=ps,
                )
            except ValueError:
                return None  # candidate cannot hold the workload
            q = jnp.asarray(
                rng.standard_normal((token_lane.shape[0], h, d)), jdt
            )
            fn = jax.jit(
                lambda q, k, v, tl, tp, pp, pl, po, pc: ragged_paged_attention(
                    q, k, v, tl, tp, pp, pl, po, pc, tb_tokens=tb,
                    pages_per_step=pps, interpret=INTERPRET,
                ).astype(q.dtype)
            )
            chain = lambda a, out: (out,) + a[1:]  # noqa: E731
            us = _time_us(
                fn, q, k, v,
                jnp.asarray(token_lane), jnp.asarray(token_pos),
                *(jnp.asarray(a) for a in meta),
                iters=iters, chain=chain,
            )
            print(json.dumps({"bench": "autotune_grid",
                              "geometry": geom.key, "dtype": "bfloat16",
                              "source": "measured", **cand,
                              "us": round(us, 1)}))
            return us

        measured = autotune.sweep(
            geom, dtype="bfloat16", buckets=buckets, runner=runner,
            device_kind=dev.device_kind,
        )
        measured.pop("grid")
        rows.append(measured)
    return rows


def bench_calibration(iters: int) -> list[dict]:
    """Self-check rows proving the timing methodology: a dependent-chain
    matmul with known FLOPs and a dependent-chain stream with known bytes.
    If achieved TFLOP/s or GB/s exceed the chip's public peaks (v5e:
    ~197 TFLOP/s bf16, ~0.82 TB/s HBM), every other row is suspect."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rows = []
    rng = np.random.default_rng(2)
    n = 256 if INTERPRET else 4096
    x = jnp.asarray(rng.standard_normal((n, n)) * 0.01, jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((n, n)) * 0.01, jnp.bfloat16)
    mm = jax.jit(lambda x, w: (x @ w) * jnp.bfloat16(0.1))
    us = _time_us(mm, x, w, iters=iters, chain=lambda a, o: (o, a[1]))
    rows.append({
        "bench": "calib_matmul", "n": n, "us": round(us, 1),
        "tflops": round(2 * n**3 / us / 1e6, 1),
    })

    m = 1 << 14 if INTERPRET else 1 << 27  # 128M bf16 elements = 256MB buffer
    a = jnp.ones((m,), jnp.bfloat16)
    # constant must be bf16-representable and != 1.0 or XLA folds the mul
    # to identity and no memory moves (1.00390625 = next bf16 above 1)
    scale = jax.jit(lambda a: a * jnp.bfloat16(1.00390625))
    us = _time_us(scale, a, iters=max(2, iters // 4),
                  chain=lambda args, o: (o,))
    rows.append({
        "bench": "calib_stream", "mb": m * 2 // 2**20, "us": round(us, 1),
        # read + write
        "gbps": round(2 * m * 2 / us / 1e3, 1),
    })
    return rows


def run_bench(out_path: str | None) -> int:
    import jax

    dev = jax.devices()[0]
    global INTERPRET
    INTERPRET = dev.platform != "tpu"
    # interpret-mode Pallas is orders of magnitude slower than compiled
    # XLA — keep iteration counts sane there; the numbers are labeled and
    # never consulted for real decisions
    iters = 2 if INTERPRET else 50
    table = {
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "interpret": INTERPRET,
        "note": (
            "interpret-mode timings: NOT hardware-representative; the "
            "engine ignores this table" if INTERPRET else
            "compiled on real hardware; attention_impl=auto consults this. "
            "autotune_ragged rows (ops/autotune.py schema v1) carry the "
            "tuned ragged-kernel configs keyed (geometry, device_kind, "
            "dtype): cost_model rows are chip-blind (device_kind=any), "
            "measured rows bind only on their exact device_kind; engine "
            "precedence is explicit DYN_AUTOTUNE_* knob > tuned row > "
            "heuristic default"
        ),
        "rows": [],
    }
    for fn in (bench_calibration, bench_attention, bench_block_copy,
               bench_ragged_packed, bench_autotune):
        try:
            rows = fn(iters)
        except Exception as exc:  # noqa: BLE001 — independent benches
            rows = [{"bench": fn.__name__, "ok": False,
                     "error": f"{type(exc).__name__}: {exc}"[:300]}]
        for row in rows:
            print(json.dumps(row))
            sys.stdout.flush()
        table["rows"].extend(rows)
    # Methodology gate: if the known-FLOPs/known-bytes calibration rows
    # exceed the chip's public peaks, the timing didn't serialize and NO
    # row in this table is trustworthy.  The engine refuses calib_ok=false
    # tables (attention_impl=auto falls back to its static heuristic).
    peaks = {"v6": (918e12, 1640.0), "v5p": (459e12, 2765.0),
             "v5": (197e12, 820.0), "v4": (275e12, 1228.0)}
    flops_peak = bw_peak = None
    for key, (fp, bw) in peaks.items():
        if key in dev.device_kind.lower():
            flops_peak, bw_peak = fp, bw
            break
    calib_ok = None
    if not INTERPRET and flops_peak is not None:
        calib_ok = True
        for row in table["rows"]:
            if row.get("bench") == "calib_matmul" and "tflops" in row:
                calib_ok &= row["tflops"] <= flops_peak / 1e12 * 1.15
            if row.get("bench") == "calib_stream" and "gbps" in row:
                calib_ok &= row["gbps"] <= bw_peak * 1.25
        if not calib_ok:
            print(json.dumps({"warning": "calibration exceeds device peaks; "
                              "table marked calib_ok=false"}))
    table["calib_ok"] = calib_ok
    if out_path:
        with open(out_path, "w") as f:
            json.dump(table, f, indent=2)
        print(json.dumps({"wrote": out_path}))
    return 0


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--bench", action="store_true",
                        help="kernel microbenchmarks instead of validation")
    parser.add_argument("--out", default=None,
                        help="write the kernel-perf table JSON here")
    args = parser.parse_args()

    import jax

    if args.bench:
        return run_bench(args.out)

    dev = jax.devices()[0]
    global INTERPRET
    INTERPRET = dev.platform != "tpu"
    print(json.dumps({"device": str(dev), "platform": dev.platform,
                      "interpret": INTERPRET}))
    failed = 0
    for name, fn in CHECKS:
        t0 = time.monotonic()
        try:
            extra = fn() or {}
            print(json.dumps({"check": name, "ok": True,
                              "s": round(time.monotonic() - t0, 1), **extra}))
        except Exception as exc:  # noqa: BLE001 — independent checks
            failed += 1
            print(json.dumps({"check": name, "ok": False,
                              "error": f"{type(exc).__name__}: {exc}"[:300]}))
        sys.stdout.flush()
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
