"""Minimal SDK graph (reference: examples/hello_world).

Three chained services; each stage decorates the text it passes along.

    python -m examples.hello_world.hello_world
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.runtime import Context, DistributedRuntime
from dynamo_tpu.runtime.client import PushRouter
from dynamo_tpu.sdk.graph import deploy_inprocess, depends, endpoint, service
from dynamo_tpu.utils.config import RuntimeConfig


@service()
class Backend:
    @endpoint()
    async def generate(self, request, ctx):
        for word in request["text"].split():
            yield {"word": f"Backend[{word}]"}


@service()
class Middle:
    backend = depends(Backend)

    @endpoint()
    async def generate(self, request, ctx):
        request["text"] = request["text"].upper()
        stream = await self.backend.generate(Context(request, ctx))
        async for item in stream:
            yield {"word": f"Middle({item['word']})"}


@service()
class Frontend:
    middle = depends(Middle)

    @endpoint()
    async def generate(self, request, ctx):
        stream = await self.middle.generate(Context(request, ctx))
        async for item in stream:
            yield item


async def run(text: str = "hello world") -> list[str]:
    rt = await DistributedRuntime.create(RuntimeConfig(control_plane="memory://hello"))
    try:
        handles = await deploy_inprocess(Frontend, rt)
        ep = rt.namespace("dynamo").component("frontend").endpoint("generate")
        router = await PushRouter.from_endpoint(ep)
        await router.client.wait_for_instances(1, timeout=5)
        out = await (await router.generate(Context({"text": text}))).collect()
        words = [o["word"] for o in out]
        for services in handles.values():
            for s in services:
                await s.shutdown(drain_timeout=1)
        return words
    finally:
        await rt.close()


if __name__ == "__main__":
    for word in asyncio.run(run()):
        print(word)
