"""Launch an LLM example graph.

    python -m examples.llm.launch agg --model /path/to/hf-model --port 8080
    python -m examples.llm.launch disagg_router -f examples/llm/configs/disagg.yaml

Runs until interrupted; serves OpenAI-compatible HTTP on the configured port.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import configure_logging, get_logger

from examples.llm.common import LlmGraphConfig
from examples.llm.graphs import GRAPHS

logger = get_logger("examples.llm")


async def amain(args: argparse.Namespace) -> int:
    cfg = LlmGraphConfig.load(
        args.config,
        **{
            k: v
            for k, v in dict(
                model_dir=args.model,
                model_name=args.model_name,
                engine_kind=args.engine,
                num_workers=args.workers,
                http_port=args.port,
            ).items()
            if v is not None
        },
    )
    rt = await DistributedRuntime.create(
        RuntimeConfig.from_env(control_plane=args.control_plane)
    )
    handle = await GRAPHS[args.graph](rt, cfg)
    logger.info(
        "graph %s up: http://%s:%d/v1/chat/completions (model=%s)",
        args.graph, cfg.http_host, handle.frontend.port, cfg.model_name,
    )
    try:
        await rt.wait_for_shutdown()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await handle.shutdown()
        await rt.close()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("graph", choices=sorted(GRAPHS))
    parser.add_argument("--model", help="local HF model dir (config.json [+ safetensors])")
    parser.add_argument("--model-name", default=None)
    parser.add_argument("--engine", default=None, choices=["jax", "mocker", "echo"])
    parser.add_argument("--workers", type=int, default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("-f", "--config", default=None, help="graph config YAML")
    parser.add_argument("--control-plane", default="memory://example")
    args = parser.parse_args()
    configure_logging()
    return asyncio.run(amain(args))


if __name__ == "__main__":
    raise SystemExit(main())
