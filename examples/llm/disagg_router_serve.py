"""Disaggregated + KV-routed serving, one OS process per deployable unit.

One command from a clean checkout:

    python -m examples.llm.disagg_router_serve --model tests/data/tiny-chat-model

brings up, under the SDK process supervisor (sdk/supervisor.py):

- the **dynctl control plane** (in this orchestrator process),
- a **frontend** process — OpenAI HTTP + preprocessor + KV-aware router,
- a **decode worker** process — JAX engine behind the remote-prefill
  decision (DisaggDecodeEngine),
- N **prefill worker** processes — pumps draining the shared prefill
  queue, shipping finished KV blocks to the decode engine over the
  transfer plane.

Then tokens stream over curl:

    curl -N http://127.0.0.1:8080/v1/chat/completions \\
      -H 'Content-Type: application/json' \\
      -d '{"model": "tiny", "stream": true, \\
           "messages": [{"role": "user", "content": "hello"}]}'

This is the reference's ``dynamo serve graphs.disagg_router:Frontend``
deployment shape (reference: examples/llm/graphs/disagg_router.py:16-24)
as separately-deployable units.  Two deliberate architectural differences:
the processor and the KV router ride inside the frontend process (one
fewer network hop per token than frontend→processor→router chains — see
docs/architecture.md); a fleet that wants routing decisions outside the
frontend deploys ``python -m dynamo_tpu.components.router_service``
instead (examples/router_standalone shows that wiring).
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger("examples.disagg_router_serve")


def _role_cmd(args: argparse.Namespace, role: str) -> list[str]:
    cmd = [
        sys.executable, "-m", "examples.llm.disagg_router_serve",
        "--role", role,
        "--control-plane", args.control_plane,
        "--model", args.model,
        "--model-name", args.model_name,
        "--port", str(args.port),
    ]
    if args.max_local_prefill_length is not None:
        cmd += ["--max-local-prefill-length", str(args.max_local_prefill_length)]
    return cmd


async def orchestrate(args: argparse.Namespace) -> int:
    from dynamo_tpu.runtime.controlplane.server import ControlPlaneServer
    from dynamo_tpu.sdk.supervisor import ProcessSpec, ProcessSupervisor

    server = ControlPlaneServer(port=args.control_plane_port)
    await server.start()
    args.control_plane = f"127.0.0.1:{server.port}"
    logger.info("control plane on %s", args.control_plane)

    sup = ProcessSupervisor()
    # everything from the first spawn onward runs under the finally, so a
    # SIGINT/exception during bring-up still tears the fleet down instead
    # of orphaning worker processes on the HTTP port
    try:
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        # workers first: the frontend's model watcher picks the model up
        # whenever registration lands, so strict ordering is not required —
        # but starting engines early overlaps their compile time
        sup.add_watcher(ProcessSpec(name="decode", cmd=_role_cmd(args, "decode")))
        sup.add_watcher(
            ProcessSpec(name="prefill", cmd=_role_cmd(args, "prefill")),
            replicas=args.prefill_workers,
        )
        sup.add_watcher(ProcessSpec(name="frontend", cmd=_role_cmd(args, "frontend")))
        await sup.start()

        print(
            f"\ndisagg_router up — {1 + 1 + args.prefill_workers} processes + "
            "control plane.\nTry:\n"
            f"  curl -N http://127.0.0.1:{args.port}/v1/chat/completions \\\n"
            "    -H 'Content-Type: application/json' \\\n"
            f"    -d '{{\"model\": \"{args.model_name}\", \"stream\": true, "
            '"messages": [{"role": "user", "content": "hello"}]}}\'\n',
            flush=True,
        )
        await stop.wait()
    finally:
        await sup.stop()
        await server.stop()
    return 0


async def run_role(args: argparse.Namespace) -> int:
    from dynamo_tpu.llm.disagg import PrefillQueue
    from dynamo_tpu.runtime.client import RouterMode
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.utils.config import RuntimeConfig

    from examples.llm.common import (
        LlmGraphConfig,
        launch_disagg_decode_worker,
        launch_frontend,
        launch_prefill_workers,
    )

    cfg = LlmGraphConfig.load(
        None,
        model_dir=args.model,
        model_name=args.model_name,
        http_port=args.port,
        num_prefill_workers=1,  # one pump per prefill PROCESS; scale via --prefill-workers
        **(
            {"max_local_prefill_length": args.max_local_prefill_length}
            if args.max_local_prefill_length is not None
            else {}
        ),
    )
    rt = await DistributedRuntime.create(
        RuntimeConfig.from_env(control_plane=args.control_plane)
    )
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, rt.shutdown)

    handles: list = []
    try:
        if args.role == "frontend":
            service, watcher = await launch_frontend(rt, cfg, RouterMode.KV)
            handles = [watcher, service]
        elif args.role == "decode":
            queue = PrefillQueue(rt, rt.config.namespace, "backend")
            handles = [await launch_disagg_decode_worker(rt, cfg, queue)]
        elif args.role == "prefill":
            queue = PrefillQueue(rt, rt.config.namespace, "backend")
            handles = list(await launch_prefill_workers(rt, cfg, queue))
        else:  # pragma: no cover — argparse choices gate this
            raise ValueError(f"unknown role {args.role}")
        logger.info("%s up", args.role)
        await rt.wait_for_shutdown()
    finally:
        for handle in reversed(handles):
            stop = getattr(handle, "shutdown", None) or getattr(handle, "stop")
            await stop()
        await rt.close()
    return 0


def main(argv: list[str] | None = None) -> int:
    configure_logging()
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--role", choices=["frontend", "decode", "prefill"])
    parser.add_argument("--model", default="tests/data/tiny-chat-model",
                        help="HF model dir (config.json [+ safetensors])")
    parser.add_argument("--model-name", default="tiny")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--prefill-workers", type=int, default=1)
    parser.add_argument("--control-plane", default=None,
                        help="(role processes) dynctl address host:port")
    parser.add_argument("--control-plane-port", type=int, default=0,
                        help="(orchestrator) dynctl listen port; 0 = ephemeral")
    parser.add_argument("--max-local-prefill-length", type=int, default=None,
                        help="prompts longer than this go to the prefill fleet")
    args = parser.parse_args(argv)
    if args.role:
        if not args.control_plane:
            parser.error("--role requires --control-plane")
        return asyncio.run(run_role(args))
    return asyncio.run(orchestrate(args))


if __name__ == "__main__":
    raise SystemExit(main())
