"""Shared launch helpers for the LLM example graphs (reference:
examples/llm/components/{worker,prefill_worker,processor}.py).

Each graph is a composition of:
- a frontend (OpenAI HTTP + model watcher) with a router mode,
- N workers (echo / mocker / JAX engine), and — for the disagg graphs —
- a decode worker wrapping :class:`DisaggDecodeEngine` plus M prefill
  workers pumping the shared prefill queue.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from dynamo_tpu.llm.disagg import (
    DisaggConfig,
    DisaggDecodeEngine,
    DisaggRouter,
    PrefillQueue,
    PrefillWorker,
)
from dynamo_tpu.llm.discovery import register_llm
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.runtime.client import RouterMode
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.serve import build_jax_engine, serve_frontend, serve_worker
from dynamo_tpu.utils.config import load_config


@dataclass
class LlmGraphConfig:
    """Per-graph config; layered defaults < YAML file < DYN_EXAMPLE_* env."""

    model_dir: str = ""
    model_name: str = "example-model"
    engine_kind: str = "jax"  # jax | mocker | echo
    num_workers: int = 1
    num_prefill_workers: int = 1
    http_host: str = "127.0.0.1"
    http_port: int = 8080
    # engine sizing
    num_blocks: int = 256
    max_batch_size: int = 8
    max_model_len: int = 1024
    # disagg decision threshold (reference: lib/llm/src/disagg_router.rs:25-34)
    max_local_prefill_length: int = 64
    max_prefill_queue_size: int = 8
    engine_overrides: dict = field(default_factory=dict)

    @classmethod
    def load(cls, config_file: str | Path | None = None, **overrides) -> "LlmGraphConfig":
        return load_config(
            cls, env_prefix="DYN_EXAMPLE", config_file=config_file, overrides=overrides
        )


@dataclass
class GraphHandle:
    """Everything a graph launched; reverse-order teardown."""

    frontend: object = None
    watcher: object = None
    workers: list = field(default_factory=list)
    extras: list = field(default_factory=list)  # objects with async stop()

    async def shutdown(self) -> None:
        if self.watcher is not None:
            await self.watcher.stop()
        if self.frontend is not None:
            await self.frontend.stop()
        for extra in reversed(self.extras):
            await extra.stop()
        for worker in reversed(self.workers):
            await worker.shutdown()


async def launch_workers(
    rt: DistributedRuntime, cfg: LlmGraphConfig, *, component: str = "backend"
) -> list:
    workers = []
    for _ in range(cfg.num_workers):
        workers.append(
            await serve_worker(
                rt,
                cfg.model_dir,
                model_name=cfg.model_name,
                component=component,
                engine_kind=cfg.engine_kind,
                **(
                    dict(
                        num_blocks=cfg.num_blocks,
                        max_batch_size=cfg.max_batch_size,
                        max_model_len=cfg.max_model_len,
                        **cfg.engine_overrides,
                    )
                    if cfg.engine_kind == "jax"
                    else {}
                ),
            )
        )
    return workers


async def launch_frontend(
    rt: DistributedRuntime, cfg: LlmGraphConfig, router_mode: RouterMode
) -> tuple:
    return await serve_frontend(
        rt, host=cfg.http_host, port=cfg.http_port, router_mode=router_mode
    )


@dataclass
class _DisaggWorkerHandle:
    service: object
    engine: DisaggDecodeEngine
    router: DisaggRouter

    async def shutdown(self) -> None:
        await self.service.shutdown()
        await self.engine.stop()
        await self.router.stop()
        self.engine.engine.stop()


@dataclass
class _PrefillHandle:
    pump: PrefillWorker
    engine: object

    async def stop(self) -> None:
        await self.pump.stop()
        self.engine.stop()


async def launch_disagg_decode_worker(
    rt: DistributedRuntime, cfg: LlmGraphConfig, queue: PrefillQueue
) -> _DisaggWorkerHandle:
    """Decode worker: JAX engine behind the remote-prefill decision wrapper
    (reference: examples/llm/components/worker.py:187)."""
    mdc = ModelDeploymentCard.from_local_path(cfg.model_dir, name=cfg.model_name)
    engine = build_jax_engine(
        cfg.model_dir,
        mdc,
        num_blocks=cfg.num_blocks,
        max_batch_size=cfg.max_batch_size,
        max_model_len=cfg.max_model_len,
        **cfg.engine_overrides,
    )
    disagg_router = DisaggRouter(
        rt,
        cfg.model_name,
        DisaggConfig(
            max_local_prefill_length=cfg.max_local_prefill_length,
            max_prefill_queue_size=cfg.max_prefill_queue_size,
        ),
    )
    await disagg_router.start()
    decode = DisaggDecodeEngine(rt, engine, disagg_router, queue)
    await decode.start()
    engine.start()
    if getattr(engine, "wants_warmup", False):
        await engine.warmup()
    ep = rt.namespace(None).component("backend").endpoint("generate")
    service = await ep.serve(decode, stats_handler=decode.stats)
    await register_llm(service, mdc)
    return _DisaggWorkerHandle(service=service, engine=decode, router=disagg_router)


async def launch_prefill_workers(
    rt: DistributedRuntime, cfg: LlmGraphConfig, queue: PrefillQueue
) -> list[_PrefillHandle]:
    """Prefill-side pumps (reference: examples/llm/components/prefill_worker.py:139)."""
    mdc = ModelDeploymentCard.from_local_path(cfg.model_dir, name=cfg.model_name)
    handles = []
    for _ in range(cfg.num_prefill_workers):
        engine = build_jax_engine(
            cfg.model_dir,
            mdc,
            num_blocks=cfg.num_blocks,
            max_batch_size=cfg.max_batch_size,
            max_model_len=cfg.max_model_len,
            **cfg.engine_overrides,
        )
        engine.start()
        if getattr(engine, "wants_warmup", False):
            await engine.warmup()
        pump = PrefillWorker(rt, engine, queue)
        pump.start()
        handles.append(_PrefillHandle(pump=pump, engine=engine))
    return handles
