from examples.llm.graphs import agg, agg_router, disagg, disagg_router

GRAPHS = {
    "agg": agg.launch,
    "agg_router": agg_router.launch,
    "disagg": disagg.launch,
    "disagg_router": disagg_router.launch,
}
