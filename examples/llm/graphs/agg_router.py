"""Aggregated serving with KV-cache-aware routing: the frontend's model
watcher builds a KvPushRouter per model, fed by worker KV events
(reference: examples/llm/graphs/agg_router.py)."""

from __future__ import annotations

from dynamo_tpu.runtime.client import RouterMode
from dynamo_tpu.runtime.distributed import DistributedRuntime

from examples.llm.common import GraphHandle, LlmGraphConfig, launch_frontend, launch_workers


async def launch(rt: DistributedRuntime, cfg: LlmGraphConfig) -> GraphHandle:
    workers = await launch_workers(rt, cfg)
    frontend, watcher = await launch_frontend(rt, cfg, RouterMode.KV)
    return GraphHandle(frontend=frontend, watcher=watcher, workers=workers)
