"""Disaggregated prefill/decode: decode worker with remote-prefill decision,
prefill workers pumping the shared queue, KV blocks shipped decode←prefill
(reference: examples/llm/graphs/disagg.py)."""

from __future__ import annotations

from dynamo_tpu.llm.disagg import PrefillQueue
from dynamo_tpu.runtime.client import RouterMode
from dynamo_tpu.runtime.distributed import DistributedRuntime

from examples.llm.common import (
    GraphHandle,
    LlmGraphConfig,
    launch_disagg_decode_worker,
    launch_frontend,
    launch_prefill_workers,
)


async def launch(
    rt: DistributedRuntime, cfg: LlmGraphConfig, router_mode: RouterMode = RouterMode.ROUND_ROBIN
) -> GraphHandle:
    queue = PrefillQueue(rt, rt.config.namespace, "backend")
    decode = await launch_disagg_decode_worker(rt, cfg, queue)
    prefills = await launch_prefill_workers(rt, cfg, queue)
    frontend, watcher = await launch_frontend(rt, cfg, router_mode)
    return GraphHandle(
        frontend=frontend, watcher=watcher, workers=[decode], extras=prefills
    )
