"""Aggregated serving: Frontend → Processor → N workers, round-robin
(reference: examples/llm/graphs/agg.py)."""

from __future__ import annotations

from dynamo_tpu.runtime.client import RouterMode
from dynamo_tpu.runtime.distributed import DistributedRuntime

from examples.llm.common import GraphHandle, LlmGraphConfig, launch_frontend, launch_workers


async def launch(rt: DistributedRuntime, cfg: LlmGraphConfig) -> GraphHandle:
    workers = await launch_workers(rt, cfg)
    frontend, watcher = await launch_frontend(rt, cfg, RouterMode.ROUND_ROBIN)
    return GraphHandle(frontend=frontend, watcher=watcher, workers=workers)
