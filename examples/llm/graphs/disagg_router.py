"""Disaggregated prefill/decode + KV-aware routing at the frontend
(reference: examples/llm/graphs/disagg_router.py:16-24)."""

from __future__ import annotations

from dynamo_tpu.runtime.client import RouterMode
from dynamo_tpu.runtime.distributed import DistributedRuntime

from examples.llm.common import GraphHandle, LlmGraphConfig
from examples.llm.graphs import disagg


async def launch(rt: DistributedRuntime, cfg: LlmGraphConfig) -> GraphHandle:
    return await disagg.launch(rt, cfg, router_mode=RouterMode.KV)
