"""Serve OpenAI ``/v1/embeddings`` with the JAX embedding engine
(reference: the embedding model type in llmctl,
launch/llmctl/src/main.rs:114-180, and /v1/embeddings
lib/llm/src/http/service/openai.rs:572-577).

    python -m examples.embeddings.serve_embeddings --model tests/data/tiny-chat-model --port 8080
"""

from __future__ import annotations

import argparse
import asyncio
import json
from pathlib import Path

from dynamo_tpu.engine.embedding import EmbeddingEngineConfig, JaxEmbeddingEngine
from dynamo_tpu.llm.http import HttpService, ModelManager
from dynamo_tpu.llm.tokenizer import HfTokenizer
from dynamo_tpu.models.registry import get_family
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger("examples.embeddings")


async def amain(model_dir: str, model_name: str, port: int, max_length: int) -> int:
    model_dir = Path(model_dir)
    hf_config = json.loads((model_dir / "config.json").read_text())
    family = get_family(hf_config.get("model_type", "llama"))
    cfg = family.config_from_hf(hf_config)
    tokenizer = HfTokenizer.from_file(model_dir / "tokenizer.json")

    params = None
    try:
        from dynamo_tpu.models.llama import load_hf_weights

        params = load_hf_weights(cfg, model_dir)
    except FileNotFoundError:
        logger.warning("no safetensors in %s — random-initializing", model_dir)

    engine = JaxEmbeddingEngine(
        EmbeddingEngineConfig(model=cfg, max_length=max_length), tokenizer, params=params
    )
    manager = ModelManager()
    manager.add_embedding_model(model_name, engine)
    service = HttpService(manager, host="127.0.0.1", port=port)
    await service.start()
    logger.info("embeddings: http://127.0.0.1:%d/v1/embeddings (model=%s)", service.port, model_name)
    try:
        await asyncio.Event().wait()
    finally:
        await service.stop()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", required=True)
    parser.add_argument("--model-name", default="embed-model")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--max-length", type=int, default=512)
    args = parser.parse_args()
    configure_logging()
    return asyncio.run(amain(args.model, args.model_name, args.port, args.max_length))


if __name__ == "__main__":
    raise SystemExit(main())
