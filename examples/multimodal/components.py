"""Multimodal encode worker as a SEPARATE runtime component.

The reference runs encoding in its own worker process and ships embeddings
to the LLM worker by descriptor (reference:
examples/multimodal/components/encode_worker.py:61 — NIXL RDMA descriptors
over NATS).  TPU hosts have no host-initiated RDMA, so the TPU-native shape
is the runtime's own data plane: the encode worker serves a control-plane
endpoint; images/frames arrive as raw bytes in the request envelope, and
the embeddings return as raw bytes through the TCP call-home stream (the
two-part codec carries binary without base64/JSON overhead — the
descriptor's job, done by the plane that already exists).

- :class:`EncodeWorkerEngine` — wire AsyncEngine over a JaxVisionEncoder:
  ``{"image_b": bytes, "shape": [H,W,3]}`` or
  ``{"frames_b": bytes, "shape": [T,H,W,3], "temporal_pool": n}`` →
  one reply ``{"embeds_b": bytes, "shape": [...], "dtype": "float32"}``.
- :func:`serve_encode_worker` — mount it on a runtime component.
- :class:`RemoteEncoder` — client used by the LLM worker's
  MultimodalEngine; same ``aencode``/``aencode_video`` surface as the
  local encoder.
"""

from __future__ import annotations

import numpy as np

from dynamo_tpu.runtime.client import PushRouter, RouterMode
from dynamo_tpu.runtime.engine import Context, ResponseStream
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("examples.multimodal.components")


def _pack(arr: np.ndarray) -> dict:
    arr = np.ascontiguousarray(arr)
    return {
        "embeds_b": arr.tobytes(),
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
    }


def _unpack(d: dict, key: str = "embeds_b") -> np.ndarray:
    return np.frombuffer(d[key], dtype=np.dtype(d["dtype"])).reshape(d["shape"]).copy()


class EncodeWorkerEngine:
    """Wire engine for the encode worker process."""

    def __init__(self, encoder):
        self.encoder = encoder  # examples.multimodal.pipeline.JaxVisionEncoder
        self.encodes = 0

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        data = request.data
        if "frames_b" in data:
            frames = np.frombuffer(data["frames_b"], np.float32).reshape(data["shape"])
            embeds = await self.encoder.aencode_video(
                frames, temporal_pool=int(data.get("temporal_pool", 2))
            )
        else:
            image = np.frombuffer(data["image_b"], np.float32).reshape(data["shape"])
            embeds = await self.encoder.aencode(image)
        self.encodes += 1
        reply = _pack(embeds)

        async def gen():
            yield reply

        return ResponseStream(gen(), request.ctx)

    def stats(self) -> dict:
        return {"encodes_total": self.encodes}


async def serve_encode_worker(
    runtime,
    encoder,
    *,
    namespace: str = "dynamo",
    component: str = "encoder",
    endpoint: str = "encode",
):
    """Mount the encoder on the control plane; returns the EndpointService."""
    ep = runtime.namespace(namespace).component(component).endpoint(endpoint)
    engine = EncodeWorkerEngine(encoder)
    service = await ep.serve(engine, stats_handler=engine.stats)
    logger.info("encode worker serving %s", ep.path)
    return service


class RemoteEncoder:
    """Encoder facade over the encode-worker component (the LLM worker's
    view): numpy in, numpy out, bytes on the wire."""

    def __init__(self, router: PushRouter):
        self.router = router

    @classmethod
    async def connect(
        cls,
        runtime,
        *,
        namespace: str = "dynamo",
        component: str = "encoder",
        endpoint: str = "encode",
        min_instances: int = 1,
        timeout: float = 30.0,
    ) -> "RemoteEncoder":
        ep = runtime.namespace(namespace).component(component).endpoint(endpoint)
        router = await PushRouter.from_endpoint(ep, mode=RouterMode.ROUND_ROBIN)
        await router.client.wait_for_instances(min_instances, timeout=timeout)
        return cls(router)

    async def _call(self, payload: dict) -> np.ndarray:
        stream = await self.router.generate(Context(payload))
        async for item in stream:
            return _unpack(item)
        raise RuntimeError("encode worker returned no embeddings")

    async def aencode(self, image: np.ndarray) -> np.ndarray:
        image = np.ascontiguousarray(np.asarray(image, np.float32))
        return await self._call(
            {"image_b": image.tobytes(), "shape": list(image.shape)}
        )

    async def aencode_video(
        self, frames: np.ndarray, *, temporal_pool: int = 2
    ) -> np.ndarray:
        frames = np.ascontiguousarray(np.asarray(frames, np.float32))
        return await self._call(
            {
                "frames_b": frames.tobytes(),
                "shape": list(frames.shape),
                "temporal_pool": temporal_pool,
            }
        )

    async def close(self) -> None:
        await self.router.client.close()
