"""Multimodal serving pipeline: encode → prefill → decode (reference:
examples/multimodal — encode_worker.py:61 produces image embeddings that the
LLM worker consumes; there embeddings travel by NIXL RDMA descriptor, here
they ride the same graph dependency channel as tensors).

Components:
- ``EncodeWorker``: JAX ViT encode + LLaVA-style projector.
- ``MultimodalEngine``: wraps a JaxLlmEngine; requests carrying an
  ``image`` (normalized [H, W, 3] floats) get their patch embeddings
  spliced before the text tokens via ``generate_multimodal``.

Run in-process:
    python -m examples.multimodal.pipeline --model tests/data/tiny-chat-model
"""

from __future__ import annotations

import argparse
import asyncio

import jax
import numpy as np

from dynamo_tpu.models.vision import (
    VisionConfig,
    init_vit_params,
    vit_encode,
    vit_encode_video,
)

# wire-facing bound: temporal_pool is a jit STATIC argument, so each
# distinct value compiles its own program — a clamp keeps a fuzzing client
# from growing the compile cache without bound
MAX_TEMPORAL_POOL = 8
from dynamo_tpu.runtime.engine import Context, ResponseStream
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger("examples.multimodal")


class JaxVisionEncoder:
    """The encode worker's engine: images/video → projected embeddings."""

    def __init__(self, cfg: VisionConfig, params=None, seed: int = 0):
        self.cfg = cfg
        self.params = params if params is not None else init_vit_params(
            cfg, jax.random.PRNGKey(seed)
        )
        self._encode = jax.jit(lambda p, imgs: vit_encode(p, cfg, imgs))
        self._encode_video = jax.jit(
            lambda p, frames, temporal_pool: vit_encode_video(
                p, cfg, frames, temporal_pool=temporal_pool
            ),
            static_argnames=("temporal_pool",),
        )

    def encode(self, image: np.ndarray) -> np.ndarray:
        """[H, W, 3] float image → [num_patches, projector_dim] float32.

        Arbitrary [H, W] inputs (the frontend ships decoded images
        unresized — geometry belongs next to the encoder that knows its
        ``image_size``) are bilinearly resized to the ViT's square input."""
        image = self._fit(image)
        out = self._encode(self.params, jax.numpy.asarray(image[None], self.cfg.dtype))
        return np.asarray(out[0], np.float32)

    def _fit(self, image: np.ndarray) -> np.ndarray:
        size = self.cfg.image_size
        if image.shape[:2] == (size, size):
            return image
        return np.asarray(
            jax.image.resize(
                jax.numpy.asarray(image, jax.numpy.float32),
                (size, size, image.shape[-1]), method="bilinear",
            )
        )

    def encode_video(self, frames: np.ndarray, *, temporal_pool: int = 2) -> np.ndarray:
        """[T, H, W, 3] frames → [ceil(T/pool)*num_patches, dim] float32."""
        if not 1 <= temporal_pool <= MAX_TEMPORAL_POOL:
            raise ValueError(
                f"temporal_pool must be in [1, {MAX_TEMPORAL_POOL}], "
                f"got {temporal_pool}"
            )
        size = self.cfg.image_size
        if frames.shape[1:3] != (size, size):
            frames = np.asarray(jax.image.resize(
                jax.numpy.asarray(frames, jax.numpy.float32),
                (frames.shape[0], size, size, frames.shape[-1]),
                method="bilinear",
            ))
        out = self._encode_video(
            self.params, jax.numpy.asarray(frames, self.cfg.dtype), temporal_pool
        )
        return np.asarray(out, np.float32)

    # async surface shared with components.RemoteEncoder (the LLM worker
    # awaits the same methods whether encoding is in-process or remote)
    async def aencode(self, image: np.ndarray) -> np.ndarray:
        return await asyncio.to_thread(self.encode, np.asarray(image, np.float32))

    async def aencode_video(
        self, frames: np.ndarray, *, temporal_pool: int = 2
    ) -> np.ndarray:
        return await asyncio.to_thread(
            lambda: self.encode_video(
                np.asarray(frames, np.float32), temporal_pool=temporal_pool
            )
        )

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        image = np.asarray(request.data["image"], np.float32)
        embeds = await self.aencode(image)

        async def gen():
            yield {"embeds": embeds.tolist()}

        return ResponseStream(gen(), request.ctx)


class MultimodalEngine:
    """AsyncEngine wrapper: image- and video-carrying requests go through
    the encoder (in-process JaxVisionEncoder or a RemoteEncoder component),
    text-only requests straight to the LLM engine."""

    def __init__(self, llm_engine, encoder):
        self.llm = llm_engine
        self.encoder = encoder

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        from dynamo_tpu.llm.multimodal import decode_image_wire

        data = dict(request.data)
        image = data.pop("image", None)
        if image is not None:
            # the frontend ships the compact b64 wire form; direct API
            # callers may still attach raw arrays/lists
            image = decode_image_wire(image)
        video = data.pop("video", None)
        temporal_pool = int(data.pop("video_temporal_pool", 2))
        if image is not None and video is not None:
            raise ValueError(
                "request carries both 'image' and 'video'; attach one "
                "modality per request"
            )
        if not 1 <= temporal_pool <= MAX_TEMPORAL_POOL:
            raise ValueError(
                f"video_temporal_pool must be in [1, {MAX_TEMPORAL_POOL}], "
                f"got {temporal_pool}"
            )
        inner = Context(data, request.ctx)
        if image is None and video is None:
            return await self.llm.generate(inner)
        if video is not None:
            embeds = await self.encoder.aencode_video(
                np.asarray(video, np.float32), temporal_pool=temporal_pool
            )
        else:
            embeds = await self.encoder.aencode(np.asarray(image, np.float32))
        return await self.llm.generate_multimodal(inner, embeds)

    def stats(self) -> dict:
        return self.llm.stats()


async def serve_http(model_dir: str, port: int, *, remote_encode: bool = False) -> int:
    """OpenAI frontend over the multimodal engine: POST an image-bearing
    chat completion (``image_url`` data:/http content part) and the image
    is decoded at the frontend, encoded by the ViT, and embedding-spliced
    ahead of the text (llm/multimodal.py; the front-door path the e2e
    tests drive)."""
    import asyncio as _asyncio
    import signal as _signal

    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.llm.http import HttpService, ModelManager
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import ChatPreprocessor
    from dynamo_tpu.llm.tokenizer import HfTokenizer
    from dynamo_tpu.serve import build_jax_engine

    mdc = ModelDeploymentCard.from_local_path(model_dir, name="mm-demo")
    tokenizer = HfTokenizer.from_model_dir(model_dir)
    llm = build_jax_engine(model_dir, mdc, num_blocks=64, max_batch_size=4,
                           max_model_len=256, prefill_buckets=(64, 128))
    llm.start()
    service = runtime = encode_service = remote = None
    try:
        vision_cfg = VisionConfig(
            **{**VisionConfig.tiny().__dict__,
               "projector_dim": llm.config.model.hidden_size}
        )
        local_encoder = JaxVisionEncoder(vision_cfg)
        if remote_encode:
            # separate-encode-worker shape (see amain): the encoder serves
            # its own runtime component and the LLM side calls it remotely
            from dynamo_tpu.runtime.distributed import DistributedRuntime
            from dynamo_tpu.utils.config import RuntimeConfig
            from examples.multimodal.components import (
                RemoteEncoder,
                serve_encode_worker,
            )

            runtime = await DistributedRuntime.create(
                RuntimeConfig(control_plane="memory://mm-serve")
            )
            encode_service = await serve_encode_worker(runtime, local_encoder)
            remote = await RemoteEncoder.connect(runtime)
            engine = MultimodalEngine(llm, remote)
        else:
            engine = MultimodalEngine(llm, local_encoder)
        manager = ModelManager()
        manager.add_chat_model(
            "mm-demo",
            ChatPreprocessor(mdc, tokenizer).wrap(Backend(tokenizer).wrap(engine)),
        )
        service = HttpService(manager, host="127.0.0.1", port=port)
        await service.start()
        print(
            f"\nmultimodal frontend on http://127.0.0.1:{service.port} — try:\n"
            "  curl -s http://127.0.0.1:%d/v1/chat/completions \\\n"
            "    -H 'Content-Type: application/json' -d '{\"model\": \"mm-demo\", "
            '"max_tokens": 16, "messages": [{"role": "user", "content": ['
            '{"type": "text", "text": "describe"}, {"type": "image_url", '
            '"image_url": {"url": "data:image/png;base64,<...>"}}]}]}\'\n'
            % service.port,
            flush=True,
        )
        stop = _asyncio.Event()
        loop = _asyncio.get_running_loop()
        for sig in (_signal.SIGINT, _signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
    finally:
        if service is not None:
            await service.stop()
        if encode_service is not None:
            await encode_service.shutdown(drain_timeout=2)
        if runtime is not None:
            await runtime.close()
        llm.stop()
    return 0


async def amain(model_dir: str, *, remote_encode: bool = False,
                video: bool = False) -> int:
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.protocols.common import (
        Annotated,
        LLMEngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.serve import build_jax_engine

    mdc = ModelDeploymentCard.from_local_path(model_dir, name="mm-demo")
    llm = build_jax_engine(model_dir, mdc, num_blocks=64, max_batch_size=4,
                           max_model_len=128, prefill_buckets=(32, 64))
    llm.start()
    vision_cfg = VisionConfig.tiny()
    # the projector must land in the LLM hidden space
    vision_cfg = VisionConfig(
        **{**vision_cfg.__dict__, "projector_dim": llm.config.model.hidden_size}
    )
    local_encoder = JaxVisionEncoder(vision_cfg)

    runtime = encode_service = remote = None
    try:
        if remote_encode:
            # the reference's separate-encode-worker shape: the encoder
            # serves its own component; the LLM side talks to it through
            # the runtime
            from dynamo_tpu.runtime.distributed import DistributedRuntime
            from dynamo_tpu.utils.config import RuntimeConfig
            from examples.multimodal.components import (
                RemoteEncoder,
                serve_encode_worker,
            )

            runtime = await DistributedRuntime.create(
                RuntimeConfig(control_plane="memory://mm-demo")
            )
            encode_service = await serve_encode_worker(runtime, local_encoder)
            remote = await RemoteEncoder.connect(runtime)
            engine = MultimodalEngine(llm, remote)
        else:
            engine = MultimodalEngine(llm, local_encoder)

        rng = np.random.default_rng(0)
        request = PreprocessedRequest(
            token_ids=[5, 6, 7],
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=8),
            eos_token_ids=[],
        ).to_wire()
        size = vision_cfg.image_size
        if video:
            request["video"] = rng.random((4, size, size, 3), np.float32).tolist()
        else:
            request["image"] = rng.random((size, size, 3), np.float32).tolist()
        stream = await engine.generate(Context(request))
        tokens = []
        async for item in stream:
            ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
            if ann.data is not None:
                tokens.extend(ann.data.token_ids)
        kind = "video" if video else "image"
        via = "remote encode worker" if remote_encode else "in-process encoder"
        print(f"generated ({kind}-conditioned, {via}):", tokens)
    finally:
        if remote is not None:
            await remote.close()
        if encode_service is not None:
            await encode_service.shutdown(drain_timeout=2)
        if runtime is not None:
            await runtime.close()
        llm.stop()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="tests/data/tiny-chat-model")
    parser.add_argument("--remote-encode", action="store_true",
                        help="serve the encoder as its own runtime component")
    parser.add_argument("--video", action="store_true",
                        help="condition on 4 video frames instead of one image")
    parser.add_argument("--serve", type=int, metavar="PORT", default=None,
                        help="serve the OpenAI frontend instead of the demo "
                        "request: image_url chat completions end to end")
    args = parser.parse_args()
    configure_logging()
    if args.serve is not None:
        return asyncio.run(
            serve_http(args.model, args.serve, remote_encode=args.remote_encode)
        )
    return asyncio.run(
        amain(args.model, remote_encode=args.remote_encode, video=args.video)
    )


if __name__ == "__main__":
    raise SystemExit(main())
