"""Multimodal serving pipeline: encode → prefill → decode (reference:
examples/multimodal — encode_worker.py:61 produces image embeddings that the
LLM worker consumes; there embeddings travel by NIXL RDMA descriptor, here
they ride the same graph dependency channel as tensors).

Components:
- ``EncodeWorker``: JAX ViT encode + LLaVA-style projector.
- ``MultimodalEngine``: wraps a JaxLlmEngine; requests carrying an
  ``image`` (normalized [H, W, 3] floats) get their patch embeddings
  spliced before the text tokens via ``generate_multimodal``.

Run in-process:
    python -m examples.multimodal.pipeline --model tests/data/tiny-chat-model
"""

from __future__ import annotations

import argparse
import asyncio

import jax
import numpy as np

from dynamo_tpu.models.vision import VisionConfig, init_vit_params, vit_encode
from dynamo_tpu.runtime.engine import Context, ResponseStream
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger("examples.multimodal")


class JaxVisionEncoder:
    """The encode worker's engine: images → projected patch embeddings."""

    def __init__(self, cfg: VisionConfig, params=None, seed: int = 0):
        self.cfg = cfg
        self.params = params if params is not None else init_vit_params(
            cfg, jax.random.PRNGKey(seed)
        )
        self._encode = jax.jit(lambda p, imgs: vit_encode(p, cfg, imgs))

    def encode(self, image: np.ndarray) -> np.ndarray:
        """[H, W, 3] float image → [num_patches, projector_dim] float32."""
        out = self._encode(self.params, jax.numpy.asarray(image[None], self.cfg.dtype))
        return np.asarray(out[0], np.float32)

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        image = np.asarray(request.data["image"], np.float32)
        embeds = await asyncio.to_thread(self.encode, image)

        async def gen():
            yield {"embeds": embeds.tolist()}

        return ResponseStream(gen(), request.ctx)


class MultimodalEngine:
    """AsyncEngine wrapper: routes image-carrying requests through the
    encoder, text-only requests straight to the LLM engine."""

    def __init__(self, llm_engine, encoder: JaxVisionEncoder):
        self.llm = llm_engine
        self.encoder = encoder

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        data = dict(request.data)
        image = data.pop("image", None)
        inner = Context(data, request.ctx)
        if image is None:
            return await self.llm.generate(inner)
        embeds = await asyncio.to_thread(self.encoder.encode, np.asarray(image, np.float32))
        return await self.llm.generate_multimodal(inner, embeds)

    def stats(self) -> dict:
        return self.llm.stats()


async def amain(model_dir: str) -> int:
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.protocols.common import (
        Annotated,
        LLMEngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.serve import build_jax_engine

    mdc = ModelDeploymentCard.from_local_path(model_dir, name="mm-demo")
    llm = build_jax_engine(model_dir, mdc, num_blocks=64, max_batch_size=4,
                           max_model_len=128, prefill_buckets=(32, 64))
    llm.start()
    vision_cfg = VisionConfig.tiny()
    # the projector must land in the LLM hidden space
    vision_cfg = VisionConfig(
        **{**vision_cfg.__dict__, "projector_dim": llm.config.model.hidden_size}
    )
    engine = MultimodalEngine(llm, JaxVisionEncoder(vision_cfg))

    rng = np.random.default_rng(0)
    image = rng.random((vision_cfg.image_size, vision_cfg.image_size, 3), np.float32)
    request = PreprocessedRequest(
        token_ids=[5, 6, 7],
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=8),
        eos_token_ids=[],
    ).to_wire()
    request["image"] = image.tolist()
    stream = await engine.generate(Context(request))
    tokens = []
    async for item in stream:
        ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
        if ann.data is not None:
            tokens.extend(ann.data.token_ids)
    print("generated (image-conditioned):", tokens)
    llm.stop()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="tests/data/tiny-chat-model")
    args = parser.parse_args()
    configure_logging()
    return asyncio.run(amain(args.model))


if __name__ == "__main__":
    raise SystemExit(main())
