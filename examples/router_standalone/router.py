"""Standalone KV-aware router with no discovery plane (reference:
examples/router_standalone/router.py:57 — the ZMQ-based router that runs
without etcd/NATS).

Workers are registered explicitly; KV events and load metrics are pushed
straight into the indexer/scheduler over plain method calls (or, across
processes, an aiohttp POST API).  Useful for embedding the routing brain in
an existing serving stack.

    python -m examples.router_standalone.router --port 8090

    POST /register   {"worker_id": 0}
    POST /events     RouterEvent JSON
    POST /metrics    ForwardPassMetrics JSON
    POST /route      {"token_ids": [...]} → {"worker_id": ..., "overlap_blocks": ...}
"""

from __future__ import annotations

import argparse
import asyncio

from aiohttp import web

from dynamo_tpu.llm.kv_router.hashing import compute_block_hashes
from dynamo_tpu.llm.kv_router.indexer import KvIndexer
from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics, RouterEvent
from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig, KvScheduler
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger("examples.router_standalone")


class StandaloneRouter:
    """Indexer + 3-term scheduler with explicit worker registration."""

    def __init__(self, *, block_size: int = 16, config: KvRouterConfig | None = None):
        self.block_size = block_size
        self.indexer = KvIndexer()
        self.scheduler = KvScheduler(config)
        self.worker_ids: set[int] = set()

    def register(self, worker_id: int) -> None:
        self.worker_ids.add(worker_id)

    def deregister(self, worker_id: int) -> None:
        self.worker_ids.discard(worker_id)
        self.indexer.remove_worker(worker_id)
        self.scheduler.remove_worker(worker_id)

    def apply_event(self, event: RouterEvent) -> None:
        self.indexer.push(event)

    def update_metrics(self, metrics: ForwardPassMetrics) -> None:
        self.scheduler.update_metrics(metrics)

    def route(self, token_ids: list[int]) -> tuple[int, int]:
        if not self.worker_ids:
            raise LookupError("no workers registered")
        hashes = compute_block_hashes(token_ids, self.block_size)
        overlaps = self.indexer.find_matches(hashes)
        worker_id, _ratio = self.scheduler.select_worker(
            sorted(self.worker_ids), overlaps, len(hashes)
        )
        return worker_id, overlaps.scores.get(worker_id, 0)


def make_app(router: StandaloneRouter) -> web.Application:
    async def register(request: web.Request) -> web.Response:
        body = await request.json()
        router.register(int(body["worker_id"]))
        return web.json_response({"ok": True})

    async def events(request: web.Request) -> web.Response:
        router.apply_event(RouterEvent.from_json(await request.read()))
        return web.json_response({"ok": True})

    async def metrics(request: web.Request) -> web.Response:
        router.update_metrics(ForwardPassMetrics.from_json(await request.read()))
        return web.json_response({"ok": True})

    async def route(request: web.Request) -> web.Response:
        body = await request.json()
        try:
            worker_id, overlap = router.route(list(body["token_ids"]))
        except LookupError as exc:
            return web.json_response({"error": str(exc)}, status=503)
        return web.json_response({"worker_id": worker_id, "overlap_blocks": overlap})

    app = web.Application()
    app.router.add_post("/register", register)
    app.router.add_post("/events", events)
    app.router.add_post("/metrics", metrics)
    app.router.add_post("/route", route)
    return app


async def amain(port: int, block_size: int) -> None:
    router = StandaloneRouter(block_size=block_size)
    router.indexer.start()
    runner = web.AppRunner(make_app(router))
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    logger.info("standalone router on :%d (block_size=%d)", port, block_size)
    try:
        await asyncio.Event().wait()
    finally:
        await runner.cleanup()
        await router.indexer.stop()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--port", type=int, default=8090)
    parser.add_argument("--block-size", type=int, default=16)
    args = parser.parse_args()
    configure_logging()
    asyncio.run(amain(args.port, args.block_size))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
